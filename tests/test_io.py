"""save/load + inference freeze + checkpoint tests (reference:
tests/unittests/test_io_save_load*, test_inference_model_io)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, optimizer


def _simple_model():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], dtype="float32")
        y = layers.fc(x, size=3, param_attr=pt.ParamAttr(name="w_io"),
                      bias_attr=pt.ParamAttr(name="b_io"))
    return main, startup, x, y


def test_save_load_params(tmp_path):
    main, startup, x, y = _simple_model()
    exe = pt.Executor()
    exe.run(startup)
    w0 = pt.global_scope().get_numpy("w_io").copy()
    pt.save_params(exe, str(tmp_path), main_program=main)
    # clobber and reload
    import jax.numpy as jnp
    pt.global_scope().set_var("w_io", jnp.zeros_like(w0))
    pt.load_params(exe, str(tmp_path), main_program=main)
    np.testing.assert_allclose(pt.global_scope().get_numpy("w_io"), w0)


def test_inference_model_roundtrip(tmp_path):
    main, startup, x, y = _simple_model()
    exe = pt.Executor()
    exe.run(startup)
    xv = np.random.RandomState(0).rand(2, 4).astype(np.float32)
    ref, = exe.run(main, feed={"x": xv}, fetch_list=[y])
    pt.save_inference_model(str(tmp_path), ["x"], [y], exe,
                            main_program=main)
    # fresh scope + load
    from paddle_tpu.framework.scope import Scope, scope_guard
    with scope_guard(Scope()):
        prog, feed_names, fetch_names = pt.load_inference_model(
            str(tmp_path), exe)
        out, = exe.run(prog, feed={feed_names[0]: xv},
                       fetch_list=fetch_names)
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_checkpoint_resume(tmp_path):
    from paddle_tpu.io import save_checkpoint, load_checkpoint
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        w = layers.create_parameter(
            [2], "float32", name="w_ck",
            default_initializer=pt.initializer.Constant(0.0))
        target = layers.fill_constant([2], "float32", 3.0)
        loss = layers.reduce_mean(layers.square(w - target))
        optimizer.Adam(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    for step in range(5):
        exe.run(main, feed={}, fetch_list=[loss])
    save_checkpoint(exe, str(tmp_path), main, step=5)
    w5 = pt.global_scope().get_numpy("w_ck").copy()
    for step in range(3):
        exe.run(main, feed={}, fetch_list=[loss])
    w8 = pt.global_scope().get_numpy("w_ck").copy()
    # resume back to step 5 state (params + adam moments restored)
    step = load_checkpoint(exe, str(tmp_path), main)
    assert step == 5
    np.testing.assert_allclose(pt.global_scope().get_numpy("w_ck"), w5)
    for _ in range(3):
        exe.run(main, feed={}, fetch_list=[loss])
    np.testing.assert_allclose(pt.global_scope().get_numpy("w_ck"), w8,
                               rtol=1e-6)


def test_inference_model_version_and_manifest(tmp_path):
    """v2 artifacts carry a format version + per-var shape/dtype manifest;
    corruption and future versions fail with NAMED errors; a v1 artifact
    (no version key — the previous release's format) still loads."""
    import json as json_mod
    import pytest
    from paddle_tpu.io import MODEL_FILE, PARAMS_FILE, \
        INFERENCE_FORMAT_VERSION

    main, startup, x, y = _simple_model()
    exe = pt.Executor()
    exe.run(startup)
    pt.save_inference_model(str(tmp_path), ["x"], [y], exe,
                            main_program=main)
    model_path = os.path.join(str(tmp_path), MODEL_FILE)
    with open(model_path) as f:
        meta = json_mod.load(f)
    assert meta["format_version"] == INFERENCE_FORMAT_VERSION
    assert meta["param_manifest"]["w_io"]["dtype"] == "float32"

    from paddle_tpu.framework.scope import Scope, scope_guard

    # 1) round-trip of the current version
    with scope_guard(Scope()):
        prog, feeds, fetches = pt.load_inference_model(str(tmp_path), exe)
        assert feeds == ["x"]

    # 2) v1 compat: strip the version + manifest keys (previous format)
    v1 = {k: v for k, v in meta.items()
          if k not in ("format_version", "param_manifest")}
    with open(model_path, "w") as f:
        json_mod.dump(v1, f)
    with scope_guard(Scope()):
        prog, feeds, fetches = pt.load_inference_model(str(tmp_path), exe)
        assert feeds == ["x"]

    # 3) future version refuses with a named error
    with open(model_path, "w") as f:
        json_mod.dump(dict(meta, format_version=99), f)
    with pytest.raises(ValueError, match="format_version 99"):
        pt.load_inference_model(str(tmp_path), exe)

    # 4) shape corruption is caught against the manifest
    bad = dict(meta)
    bad["param_manifest"] = dict(meta["param_manifest"],
                                 w_io={"shape": [999, 3],
                                       "dtype": "float32"})
    with open(model_path, "w") as f:
        json_mod.dump(bad, f)
    with pytest.raises(ValueError, match="w_io.*shape"):
        pt.load_inference_model(str(tmp_path), exe)

    # 5) missing var named in the error
    bad["param_manifest"] = dict(meta["param_manifest"],
                                 ghost_var={"shape": [1],
                                            "dtype": "float32"})
    with open(model_path, "w") as f:
        json_mod.dump(bad, f)
    with pytest.raises(ValueError, match="ghost_var"):
        pt.load_inference_model(str(tmp_path), exe)


def test_sharded_checkpoint_reshard_dp2mp2_to_dp4mp2(tmp_path):
    """Pod-scale checkpoint contract (ref fluid.io:347
    _save_distributed_persistables): train dp2 x mp2 with ZeRO-1 sharded
    Adam moments, save per-shard, restore onto a DIFFERENT topology
    (dp4 x mp2) and continue — losses must match the unsaved run."""
    import json as json_mod
    from paddle_tpu.io import save_checkpoint, load_checkpoint
    from paddle_tpu.framework.compiler import CompiledProgram, BuildStrategy
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.distributed import fleet, column_parallel_attr, \
        row_parallel_attr
    from paddle_tpu.distributed.mesh import DistributedStrategy

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [32], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        h = layers.fc(x, size=64, act="gelu",
                      param_attr=column_parallel_attr(name="ck_w1"))
        h2 = layers.fc(h, size=32, param_attr=row_parallel_attr(name="ck_w2"))
        logits = layers.fc(h2, size=8)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        strategy = DistributedStrategy()
        strategy.sharding_optimizer_state = True   # ZeRO-1
        fleet.distributed_optimizer(optimizer.Adam(1e-3),
                                    strategy).minimize(loss)

    rng = np.random.RandomState(7)
    feeds = [{"x": rng.rand(8, 32).astype(np.float32),
              "y": rng.randint(0, 8, (8, 1)).astype(np.int64)}
             for _ in range(5)]

    def run_losses(exe, compiled, fs):
        return [float(np.asarray(exe.run(compiled, feed=f,
                                         fetch_list=[loss])[0])
                      .reshape(-1)[0]) for f in fs]

    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        bs = BuildStrategy()
        bs.mesh_axes = {"dp": 2, "mp": 2}
        compiled = CompiledProgram(main, bs)
        run_losses(exe, compiled, feeds[:3])
        save_checkpoint(exe, str(tmp_path), main, step=3)
        ref = run_losses(exe, compiled, feeds[3:])

    # the on-disk layout is genuinely per-shard, not a host-gather blob
    with open(os.path.join(str(tmp_path), "step_3", "manifest.json")) as f:
        manifest = json_mod.load(f)
    assert manifest["format_version"] == 1
    sharded_vars = [n for n, v in manifest["vars"].items()
                    if len(v["shards"]) > 1]
    assert sharded_vars, "expected mp weights/ZeRO moments in shards"

    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)   # cold init, clobbered by the restore
        step = load_checkpoint(exe, str(tmp_path), main)
        assert step == 3
        bs = BuildStrategy()
        bs.mesh_axes = {"dp": 4, "mp": 2}
        compiled = CompiledProgram(main, bs)
        got = run_losses(exe, compiled, feeds[3:])
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_async_checkpoint_commit(tmp_path):
    """blocking=False: device->host copy is synchronous (donation
    safety) but the file commit happens on a background thread; the
    handle, a follow-up save, and load_checkpoint all join it."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.io import save_checkpoint, load_checkpoint, \
        wait_for_pending_saves
    from paddle_tpu.framework.scope import Scope, scope_guard

    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    sh = NamedSharding(mesh, P("dp"))
    w1 = jax.device_put(np.arange(16, dtype=np.float32).reshape(4, 4), sh)
    sc = Scope()
    with scope_guard(sc):
        sc.set_var("w_async", w1)
        h = save_checkpoint(None, str(tmp_path), step=1, blocking=False)
        assert h is not None
        h.result(timeout=30)
        assert h.done()
        # second async save while nothing pending; then mutate state and
        # save step 3 — load must see the LATEST committed step
        sc.set_var("w_async", jax.device_put(
            np.arange(16, dtype=np.float32).reshape(4, 4) * 2, sh))
        save_checkpoint(None, str(tmp_path), step=3, blocking=False)
    sc2 = Scope()
    with scope_guard(sc2):
        step = load_checkpoint(None, str(tmp_path))   # joins the commit
        assert step == 3
        np.testing.assert_allclose(
            np.asarray(sc2.find_var("w_async")),
            np.arange(16, dtype=np.float32).reshape(4, 4) * 2)
    wait_for_pending_saves()


def test_sharded_checkpoint_torn_manifest_hard_error(tmp_path):
    """A manifest whose shard list no longer tiles a var must raise, not
    restore uninitialized memory."""
    import json as json_mod
    import jax
    import pytest
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.io import save_checkpoint, load_checkpoint
    from paddle_tpu.framework.scope import Scope, scope_guard

    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    w = jax.device_put(np.arange(16, dtype=np.float32).reshape(4, 4),
                       NamedSharding(mesh, P("dp")))
    sc = Scope()
    with scope_guard(sc):
        sc.set_var("w_torn", w)
        save_checkpoint(None, str(tmp_path), step=1)
    mpath = os.path.join(str(tmp_path), "step_1", "manifest.json")
    with open(mpath) as f:
        manifest = json_mod.load(f)
    manifest["vars"]["w_torn"]["shards"] = \
        manifest["vars"]["w_torn"]["shards"][:-1]   # drop one tile
    with open(mpath, "w") as f:
        json_mod.dump(manifest, f)
    with scope_guard(Scope()):
        with pytest.raises(ValueError, match="w_torn.*cover"):
            load_checkpoint(None, str(tmp_path))


def test_sharded_checkpoint_direct_mesh_load(tmp_path):
    """shardings= load path: vars materialize straight onto the current
    mesh via make_array_from_callback, no host round-trip for the full
    array."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.io import save_checkpoint, load_checkpoint
    from paddle_tpu.framework.scope import Scope, scope_guard

    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("dp", "mp"))
    sh = NamedSharding(mesh, P("dp", "mp"))
    w = jax.device_put(np.arange(64, dtype=np.float32).reshape(8, 8), sh)
    sc = Scope()
    with scope_guard(sc):
        sc.set_var("w_direct", w)
        sc.set_var("counter", np.int64(7))
        save_checkpoint(None, str(tmp_path), step=1)

    devs2 = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh2 = Mesh(devs2, ("dp", "mp"))
    sh2 = NamedSharding(mesh2, P("dp", "mp"))
    sc = Scope()
    with scope_guard(sc):
        step = load_checkpoint(None, str(tmp_path),
                               shardings={"w_direct": sh2})
        assert step == 1
        got = sc.find_var("w_direct")
        assert isinstance(got, jax.Array)
        assert got.sharding == sh2
        np.testing.assert_allclose(
            np.asarray(got), np.arange(64, dtype=np.float32).reshape(8, 8))
        assert int(np.asarray(sc.find_var("counter"))) == 7


def test_program_clone_for_test_dropout_deterministic():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8], dtype="float32")
        d = layers.dropout(layers.fc(x, 8), 0.5,
                           dropout_implementation="upscale_in_train")
        out = layers.reduce_sum(d)
    test_prog = main.clone(for_test=True)
    exe = pt.Executor()
    exe.run(startup)
    xv = np.ones((2, 8), np.float32)
    a, = exe.run(test_prog, feed={"x": xv}, fetch_list=[out])
    b, = exe.run(test_prog, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(a, b)  # no randomness in test mode


def test_py_reader_train_loop_and_eof():
    from paddle_tpu import layers, optimizer
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        reader = layers.py_reader(capacity=4,
                                  shapes=[(8, 4), (8, 1)],
                                  dtypes=["float32", "float32"])
        x, y = layers.read_file(reader)
        pred = layers.fc(x, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
        optimizer.SGD(0.1).minimize(loss)

    rng = np.random.RandomState(0)
    W = rng.randn(4, 1).astype(np.float32)

    def batches():
        for _ in range(12):
            xv = rng.randn(8, 4).astype(np.float32)
            yield xv, xv @ W

    reader.decorate_tensor_provider(batches)
    exe = pt.Executor()
    exe.run(startup)
    for epoch in range(2):
        reader.start()
        losses = []
        while True:
            try:
                lv, = exe.run(main, fetch_list=[loss])
            except layers.EOFException:
                reader.reset()
                break
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert len(losses) == 12
    assert losses[-1] < losses[0]


def test_py_reader_paddle_reader_decoration():
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        reader = layers.py_reader(capacity=2, shapes=[(4, 2)],
                                  dtypes=["float32"])
        x = layers.read_file(reader)
        out = layers.scale(x, scale=2.0)

    def sample_batches():
        yield [(np.ones(2, np.float32) * i,) for i in range(4)]

    reader.decorate_paddle_reader(sample_batches)
    reader.start()
    exe = pt.Executor()
    exe.run(startup)
    ov, = exe.run(main, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(ov)[:, 0], [0, 2, 4, 6])
    reader.reset()


def test_py_func_forward_and_backward():
    from paddle_tpu import layers, optimizer
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("pf_x", [4, 3], "float32", append_batch_size=False)
        w = layers.create_parameter(
            [4, 3], "float32", name="pf_w",
            default_initializer=pt.initializer.Constant(2.0))
        xw = layers.elementwise_mul(x, w)
        out = main.global_block().create_var(
            name="pf_out", shape=(4, 3), dtype="float32")
        layers.py_func(
            func=lambda a: np.sin(a),
            x=xw, out=out,
            backward_func=lambda a, o, g: g * np.cos(a))
        loss = layers.reduce_sum(out)
        optimizer.SGD(0.0).minimize(loss)   # forces backward through py_func
        grads = pt.gradients(loss, [w])
    exe = pt.Executor()
    exe.run(startup)
    xv = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    ov, gv = exe.run(main, feed={"pf_x": xv}, fetch_list=[out, grads[0]])
    np.testing.assert_allclose(np.asarray(ov), np.sin(xv * 2.0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gv), np.cos(xv * 2.0) * xv,
                               rtol=1e-4, atol=1e-5)


def test_py_func_no_backward_stops_gradient():
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("pf2_x", [2, 2], "float32", append_batch_size=False)
        out = main.global_block().create_var(
            name="pf2_out", shape=(2, 2), dtype="float32")
        layers.py_func(func=lambda a: a * 3.0, x=x, out=out)
    exe = pt.Executor()
    exe.run(startup)
    xv = np.ones((2, 2), np.float32)
    ov, = exe.run(main, feed={"pf2_x": xv}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(ov), xv * 3.0)


def test_py_func_integer_input_float0_cotangent():
    """Mixed float+int inputs: int primals must get float0 cotangents."""
    from paddle_tpu import layers, optimizer
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("pfi_x", [2, 3], "float32", append_batch_size=False)
        idx = layers.data("pfi_i", [2, 3], "int64", append_batch_size=False)
        w = layers.create_parameter(
            [2, 3], "float32", name="pfi_w",
            default_initializer=pt.initializer.Constant(1.0))
        xw = layers.elementwise_mul(x, w)
        out = main.global_block().create_var(
            name="pfi_out", shape=(2, 3), dtype="float32")
        layers.py_func(
            func=lambda a, i: a * (i + 1),
            x=[xw, idx], out=out,
            backward_func=lambda a, i, o, g: (g * (i + 1), None))
        loss = layers.reduce_sum(out)
        optimizer.SGD(0.0).minimize(loss)
        grads = pt.gradients(loss, [w])
    exe = pt.Executor()
    exe.run(startup)
    xv = np.ones((2, 3), np.float32) * 2
    iv = np.arange(6, dtype=np.int64).reshape(2, 3)
    ov, gv = exe.run(main, feed={"pfi_x": xv, "pfi_i": iv},
                     fetch_list=[out, grads[0]])
    np.testing.assert_allclose(np.asarray(ov), xv * (iv + 1))
    np.testing.assert_allclose(np.asarray(gv), xv * (iv + 1))


def test_py_reader_mid_epoch_reset_no_stale_batches():
    """reset() while the filler thread is blocked must not leak a stale
    batch or EOF sentinel into the next epoch."""
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        reader = layers.py_reader(capacity=2, shapes=[(2, 2)],
                                  dtypes=["float32"])
        x = layers.read_file(reader)
        out = layers.scale(x, scale=1.0)

    def epoch_batches(tag):
        def gen():
            for i in range(10):
                yield (np.full((2, 2), tag * 100 + i, np.float32),)
        return gen

    exe = pt.Executor()
    exe.run(startup)
    reader.decorate_tensor_provider(epoch_batches(1))
    reader.start()
    ov, = exe.run(main, fetch_list=[out])   # consume one batch
    assert float(np.asarray(ov)[0, 0]) == 100.0
    reader.reset()                           # filler still mid-stream
    reader.decorate_tensor_provider(epoch_batches(2))
    reader.start()
    ov, = exe.run(main, fetch_list=[out])
    assert float(np.asarray(ov)[0, 0]) == 200.0  # fresh epoch, not stale
    reader.reset()


def _two_step_ckpt_dir(tmp_path):
    """Scope with one var checkpointed at steps 1 (value 1s) and 2
    (value 2s); returns the dir. 'latest' points at step_2."""
    import jax.numpy as jnp
    from paddle_tpu.io import save_checkpoint
    from paddle_tpu.framework.scope import Scope, scope_guard
    sc = Scope()
    with scope_guard(sc):
        sc.set_var("w_q", jnp.ones(4, jnp.float32))
        save_checkpoint(None, str(tmp_path), step=1)
        sc.set_var("w_q", jnp.ones(4, jnp.float32) * 2)
        save_checkpoint(None, str(tmp_path), step=2)
    return str(tmp_path)


@pytest.mark.faultinject
def test_load_checkpoint_quarantines_corrupt_manifest(tmp_path):
    """A torn/corrupt manifest must not fail the restore: the bad step
    dir is renamed step_N.corrupt and the previous valid checkpoint
    loads instead (satellite of the resilience PR)."""
    from paddle_tpu.framework import resilience
    from paddle_tpu.io import load_checkpoint
    from paddle_tpu.framework.scope import Scope, scope_guard
    d = _two_step_ckpt_dir(tmp_path)
    resilience.clear_events()
    with open(os.path.join(d, "step_2", "manifest.json"), "w") as f:
        f.write("{ not json")
    sc = Scope()
    with scope_guard(sc):
        assert load_checkpoint(None, d) == 1
        np.testing.assert_allclose(np.asarray(sc.find_var("w_q")),
                                   np.ones(4))
    assert os.path.isdir(os.path.join(d, "step_2.corrupt"))
    assert not os.path.exists(os.path.join(d, "step_2"))
    # the pointer was repaired to the checkpoint actually restored
    with open(os.path.join(d, "latest")) as f:
        assert f.read().strip() == "step_1"
    assert resilience.events("ckpt_quarantine")


@pytest.mark.faultinject
def test_load_checkpoint_quarantines_missing_shards(tmp_path):
    from paddle_tpu.io import load_checkpoint
    from paddle_tpu.framework.scope import Scope, scope_guard
    d = _two_step_ckpt_dir(tmp_path)
    os.unlink(os.path.join(d, "step_2", "shards_p0.npz"))
    sc = Scope()
    with scope_guard(sc):
        assert load_checkpoint(None, d) == 1
        np.testing.assert_allclose(np.asarray(sc.find_var("w_q")),
                                   np.ones(4))
    assert os.path.isdir(os.path.join(d, "step_2.corrupt"))


def test_load_checkpoint_missing_latest_pointer(tmp_path):
    from paddle_tpu.io import load_checkpoint
    from paddle_tpu.framework.scope import Scope, scope_guard
    d = _two_step_ckpt_dir(tmp_path)
    os.unlink(os.path.join(d, "latest"))
    sc = Scope()
    with scope_guard(sc):
        assert load_checkpoint(None, d) == 2   # newest valid step dir
        np.testing.assert_allclose(np.asarray(sc.find_var("w_q")),
                                   np.ones(4) * 2)


def test_load_checkpoint_stale_latest_pointer(tmp_path):
    from paddle_tpu.io import _atomic_write, load_checkpoint
    from paddle_tpu.framework.scope import Scope, scope_guard
    d = _two_step_ckpt_dir(tmp_path)
    _atomic_write(os.path.join(d, "latest"), "step_99")   # never written
    with scope_guard(Scope()):
        assert load_checkpoint(None, d) == 2


def test_load_checkpoint_all_corrupt_raises_first_error(tmp_path):
    from paddle_tpu.io import load_checkpoint
    from paddle_tpu.framework.scope import Scope, scope_guard
    d = _two_step_ckpt_dir(tmp_path)
    for s in ("step_1", "step_2"):
        os.unlink(os.path.join(d, s, "shards_p0.npz"))
    with scope_guard(Scope()):
        with pytest.raises(OSError):
            load_checkpoint(None, d)
    # nothing valid left, both quarantined for forensics
    assert os.path.isdir(os.path.join(d, "step_1.corrupt"))
    assert os.path.isdir(os.path.join(d, "step_2.corrupt"))


def test_save_checkpoint_prunes_past_quarantined_dirs(tmp_path):
    """keep_last pruning must skip step_N.corrupt dirs: the first save
    after a quarantine used to die on int('2.corrupt')."""
    import jax.numpy as jnp
    from paddle_tpu.io import load_checkpoint, save_checkpoint
    from paddle_tpu.framework.scope import Scope, scope_guard
    d = _two_step_ckpt_dir(tmp_path)
    os.unlink(os.path.join(d, "step_2", "shards_p0.npz"))
    sc = Scope()
    with scope_guard(sc):
        assert load_checkpoint(None, d) == 1   # quarantines step_2
        sc.set_var("w_q", jnp.ones(4, jnp.float32) * 3)
        save_checkpoint(None, d, step=3, keep_last=1)
    assert os.path.isdir(os.path.join(d, "step_3"))
    assert not os.path.exists(os.path.join(d, "step_1"))   # pruned
    # forensics dir survives keep_last
    assert os.path.isdir(os.path.join(d, "step_2.corrupt"))


def test_keep_last_retention_counts_only_scrub_valid_dirs(tmp_path):
    """Scrub-aware pruning: a burst of torn saves (shards on disk, no
    manifest — the mid-commit-crash shape) must NOT consume keep_last
    retention slots. Under count-all-dirs retention the burst evicts
    every restorable checkpoint and keeps only wreckage; under
    scrub-aware retention the newest keep_last VALID checkpoints
    survive, torn dirs newer than the cutoff are left alone (an
    in-flight async commit looks identical), and torn dirs OLDER than
    the cutoff are pruned with everything else."""
    import jax.numpy as jnp
    from paddle_tpu.io import save_checkpoint, scrub_checkpoint
    from paddle_tpu.framework.scope import Scope, scope_guard
    d = str(tmp_path / "ckpt")
    sc = Scope()
    with scope_guard(sc):
        sc.set_var("w_r", jnp.ones(4, jnp.float32))
        save_checkpoint(None, d, step=0, keep_last=2)
        # a torn save OLDER than the soon-to-be retention window
        os.makedirs(os.path.join(d, "step_1"))
        with open(os.path.join(d, "step_1", "shards_p0.npz"), "wb"):
            pass
        save_checkpoint(None, d, step=3, keep_last=2)
        # burst of torn saves newer than every valid checkpoint
        for s in (4, 5, 6, 7, 8):
            os.makedirs(os.path.join(d, "step_%d" % s))
            with open(os.path.join(d, "step_%d" % s, "shards_p0.npz"),
                      "wb"):
                pass
        save_checkpoint(None, d, step=9, keep_last=2)
    report = scrub_checkpoint(d)
    # the two newest VALID checkpoints survived the burst...
    assert report["valid_steps"] == [3, 9]
    assert os.path.isdir(os.path.join(d, "step_3"))
    # ...the valid dir beyond retention was pruned, and so was the torn
    # dir older than the retention cutoff
    assert not os.path.exists(os.path.join(d, "step_0"))
    assert not os.path.exists(os.path.join(d, "step_1"))
    # torn dirs NEWER than the cutoff stay (async-commit safety)
    for s in (4, 5, 6, 7, 8):
        assert os.path.isdir(os.path.join(d, "step_%d" % s))
    assert report["steps"][4]["status"] == "incomplete"
    # keep_last<=0 prunes NOTHING (historical behavior — it must never
    # delete the checkpoint that was just committed)
    with scope_guard(sc):
        save_checkpoint(None, d, step=12, keep_last=0)
    assert os.path.isdir(os.path.join(d, "step_12"))
    assert os.path.isdir(os.path.join(d, "step_9"))
    assert os.path.isdir(os.path.join(d, "step_3"))


def test_load_checkpoint_caller_side_error_not_quarantined(tmp_path,
                                                           monkeypatch):
    """A restore that fails for a CALLER-side reason (e.g. a bad
    shardings entry) while the step dir is healthy on disk must
    re-raise — not rename valid history one .corrupt at a time."""
    import paddle_tpu.io as io_mod
    from paddle_tpu.framework.scope import Scope, scope_guard
    d = _two_step_ckpt_dir(tmp_path)

    def boom(*a, **k):
        raise ValueError("caller-side restore bug")
    monkeypatch.setattr(io_mod, "_stitch", boom)
    with scope_guard(Scope()):
        with pytest.raises(ValueError, match="caller-side"):
            io_mod.load_checkpoint(None, d)
    assert os.path.isdir(os.path.join(d, "step_2"))
    assert not os.path.exists(os.path.join(d, "step_2.corrupt"))
    assert os.path.isdir(os.path.join(d, "step_1"))


def test_load_checkpoint_newer_format_is_not_quarantined(tmp_path):
    """A checkpoint written by a NEWER library is healthy, not corrupt:
    it must surface CheckpointFormatError and keep its step dir."""
    import json as json_mod
    from paddle_tpu.io import CheckpointFormatError, load_checkpoint
    from paddle_tpu.framework.scope import Scope, scope_guard
    d = _two_step_ckpt_dir(tmp_path)
    mpath = os.path.join(d, "step_2", "manifest.json")
    with open(mpath) as f:
        manifest = json_mod.load(f)
    manifest["format_version"] = 999
    with open(mpath, "w") as f:
        json_mod.dump(manifest, f)
    with scope_guard(Scope()):
        with pytest.raises(CheckpointFormatError, match="newer"):
            load_checkpoint(None, d)
    assert os.path.isdir(os.path.join(d, "step_2"))   # NOT renamed


@pytest.mark.faultinject
def test_async_checkpoint_failure_raises_exactly_once(tmp_path):
    """Satellite: a failed blocking=False commit surfaces exactly once
    from wait_for_pending_saves() and does not poison the next save."""
    import jax.numpy as jnp
    from paddle_tpu.framework import resilience
    from paddle_tpu.io import (load_checkpoint, save_checkpoint,
                               wait_for_pending_saves)
    from paddle_tpu.framework.scope import Scope, scope_guard
    sc = Scope()
    with scope_guard(sc):
        sc.set_var("w_once", jnp.arange(4, dtype=jnp.float32))
        with resilience.inject("ckpt_write:io_error@1"):
            h = save_checkpoint(None, str(tmp_path), step=1,
                                blocking=False)
            assert h is not None
            with pytest.raises(OSError, match="injected checkpoint"):
                wait_for_pending_saves()
            wait_for_pending_saves()       # second join: clean no-op
            # next save commits fine (fault was one-shot @1)
            sc.set_var("w_once", jnp.arange(4, dtype=jnp.float32) * 3)
            save_checkpoint(None, str(tmp_path), step=2, blocking=False)
    sc2 = Scope()
    with scope_guard(sc2):
        assert load_checkpoint(None, str(tmp_path)) == 2
        np.testing.assert_allclose(np.asarray(sc2.find_var("w_once")),
                                   np.arange(4, dtype=np.float32) * 3)


# ---------------------------------------------------------------------------
# scrub_checkpoint: cheap supervisor-side validation (pod-recovery PR)
# ---------------------------------------------------------------------------

def _forbid_payload_reads(monkeypatch):
    """Any NpzFile payload read during the block under test is a hard
    failure: the scrub must classify from manifest JSON and npz member
    lists (the zip central directory) alone."""
    def boom(self, key):
        raise AssertionError(
            "scrub_checkpoint read shard payload %r — it must stay on "
            "manifests and npz member lists" % key)
    monkeypatch.setattr(np.lib.npyio.NpzFile, "__getitem__", boom)


def test_scrub_checkpoint_classifies_without_payload_reads(tmp_path,
                                                           monkeypatch):
    """Acceptance: every step dir is classified valid / corrupt /
    incomplete with zero shard-payload loads, and valid_steps agrees
    with what load_checkpoint could actually restore."""
    import shutil
    from paddle_tpu.io import scrub_checkpoint
    d = _two_step_ckpt_dir(tmp_path)           # step_1, step_2: valid
    # step_3: shards landed, manifest never did — a torn/in-flight save
    os.makedirs(os.path.join(d, "step_3"))
    shutil.copy(os.path.join(d, "step_1", "shards_p0.npz"),
                os.path.join(d, "step_3", "shards_p0.npz"))
    # step_4: manifest committed but its shard file is gone — corrupt
    shutil.copytree(os.path.join(d, "step_2"), os.path.join(d, "step_4"))
    os.unlink(os.path.join(d, "step_4", "shards_p0.npz"))
    # step_5: an empty dir — the save died before any bytes
    os.makedirs(os.path.join(d, "step_5"))
    # a previously-quarantined dir is reported, never reclassified
    shutil.copytree(os.path.join(d, "step_2"),
                    os.path.join(d, "step_9.corrupt"))

    _forbid_payload_reads(monkeypatch)
    report = scrub_checkpoint(d)
    assert report["dirname"] == d
    assert report["latest"] == "step_2"
    assert report["valid_steps"] == [1, 2]
    statuses = {s: v["status"] for s, v in report["steps"].items()}
    assert statuses == {1: "valid", 2: "valid", 3: "incomplete",
                        4: "corrupt", 5: "incomplete"}
    assert "no manifest" in report["steps"][3]["reason"]
    assert "shard file" in report["steps"][4]["reason"]
    assert report["quarantined"] == ["step_9.corrupt"]
    # read-only: the scrub never renamed/quarantined anything itself
    assert sorted(x for x in os.listdir(d) if x.startswith("step_")) == [
        "step_1", "step_2", "step_3", "step_4", "step_5",
        "step_9.corrupt"]
    # observability: one structured event with the tallies
    from paddle_tpu.framework import resilience
    ev = resilience.events("scrub")[-1]
    assert (ev["valid"], ev["corrupt"], ev["incomplete"]) == (2, 1, 2)


def test_scrub_checkpoint_corrupt_manifest_and_missing_keys(tmp_path,
                                                            monkeypatch):
    import json as json_mod
    import shutil
    from paddle_tpu.io import scrub_checkpoint
    d = _two_step_ckpt_dir(tmp_path)
    # torn manifest (truncated JSON)
    with open(os.path.join(d, "step_1", "manifest.json"), "w") as f:
        f.write('{"vars": {"w_q"')
    # manifest references a key the shard npz does not hold
    mpath = os.path.join(d, "step_2", "manifest.json")
    with open(mpath) as f:
        manifest = json_mod.load(f)
    next(iter(manifest["vars"].values()))["shards"][0]["key"] = "ghost"
    with open(mpath, "w") as f:
        json_mod.dump(manifest, f)
    _forbid_payload_reads(monkeypatch)
    report = scrub_checkpoint(d)
    assert report["valid_steps"] == []
    assert report["steps"][1]["status"] == "corrupt"
    assert "manifest" in report["steps"][1]["reason"]
    assert report["steps"][2]["status"] == "corrupt"
    assert "missing keys" in report["steps"][2]["reason"]


def test_scrub_checkpoint_newer_format_is_valid_but_not_restorable(
        tmp_path, monkeypatch):
    """A healthy checkpoint from a NEWER library is 'valid' (never a
    quarantine candidate) but excluded from valid_steps — THIS library
    cannot restore it, so the pod must not elect it."""
    import json as json_mod
    from paddle_tpu.io import scrub_checkpoint
    d = _two_step_ckpt_dir(tmp_path)
    mpath = os.path.join(d, "step_2", "manifest.json")
    with open(mpath) as f:
        manifest = json_mod.load(f)
    manifest["format_version"] = 999
    with open(mpath, "w") as f:
        json_mod.dump(manifest, f)
    _forbid_payload_reads(monkeypatch)
    report = scrub_checkpoint(d)
    assert report["steps"][2]["status"] == "valid"
    assert "newer" in report["steps"][2]["reason"]
    assert report["valid_steps"] == [1]


def test_scrub_checkpoint_missing_dir_is_empty_report(tmp_path):
    from paddle_tpu.io import scrub_checkpoint
    report = scrub_checkpoint(str(tmp_path / "never_written"))
    assert report["valid_steps"] == [] and report["steps"] == {}
    assert report["latest"] is None


def test_scrub_agrees_with_load_checkpoint_quarantine(tmp_path):
    """The supervisor's scrub and load_checkpoint's quarantine run the
    SAME classifier: what the scrub calls restorable, the load restores;
    what it flags, the load quarantines."""
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.io import load_checkpoint, scrub_checkpoint
    d = _two_step_ckpt_dir(tmp_path)
    os.unlink(os.path.join(d, "step_2", "shards_p0.npz"))
    report = scrub_checkpoint(d)
    assert report["valid_steps"] == [1]
    assert report["steps"][2]["status"] == "corrupt"
    with scope_guard(Scope()):
        assert load_checkpoint(None, d) == max(report["valid_steps"])
    assert os.path.isdir(os.path.join(d, "step_2.corrupt"))


def test_py_func_skip_vars_rejected():
    import pytest
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("pfs_x", [2, 2], "float32", append_batch_size=False)
        out = main.global_block().create_var(
            name="pfs_out", shape=(2, 2), dtype="float32")
        with pytest.raises(NotImplementedError):
            layers.py_func(func=lambda a: a, x=x, out=out,
                           backward_func=lambda a, o, g: g,
                           skip_vars_in_backward_input=[x])


@pytest.mark.faultinject
def test_manifest_write_fault_never_publishes_torn_step(tmp_path):
    """ISSUE-17 durability proof, driven through the fault plane: kill
    the save at the ``io.manifest_write`` failpoint — shards on disk,
    commit record not — and the torn step must be invisible to every
    reader path. 'latest' still names the previous step (the manifest
    IS the commit, and it never landed), scrub classifies the dir
    incomplete, and a pointer-less restore quarantines it instead of
    trusting it."""
    import jax.numpy as jnp
    from paddle_tpu.framework import faultinject, resilience
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.io import load_checkpoint, save_checkpoint, \
        scrub_checkpoint
    d = _two_step_ckpt_dir(tmp_path)
    sc = Scope()
    with scope_guard(sc):
        sc.set_var("w_q", jnp.ones(4, jnp.float32) * 3)
        with faultinject.failpoints(["io.manifest_write:raise"]):
            with pytest.raises(OSError, match="manifest_write"):
                save_checkpoint(None, d, step=3)
            assert faultinject.hits_total()["io.manifest_write"] == 1
    # torn on disk exactly as the commit order promises: payload bytes
    # are present, the commit record is not
    assert os.path.exists(os.path.join(d, "step_3", "shards_p0.npz"))
    assert not os.path.exists(
        os.path.join(d, "step_3", "manifest.json"))
    with open(os.path.join(d, "latest")) as f:
        assert f.read().strip() == "step_2"   # never advanced
    report = scrub_checkpoint(d)
    assert report["steps"][3]["status"] == "incomplete"
    assert report["valid_steps"] == [1, 2]
    # restore path 1: the honest pointer means the torn dir is never
    # even consulted
    s2 = Scope()
    with scope_guard(s2):
        assert load_checkpoint(None, d) == 2
        np.testing.assert_allclose(np.asarray(s2.find_var("w_q")),
                                   np.ones(4) * 2)
    # restore path 2: even with the pointer gone (newest-first scan),
    # the torn dir is quarantined, not restored from
    os.unlink(os.path.join(d, "latest"))
    resilience.clear_events()
    s3 = Scope()
    with scope_guard(s3):
        assert load_checkpoint(None, d) == 2
        np.testing.assert_allclose(np.asarray(s3.find_var("w_q")),
                                   np.ones(4) * 2)
    assert os.path.isdir(os.path.join(d, "step_3.corrupt"))
    assert not os.path.exists(os.path.join(d, "step_3"))
    assert resilience.events("ckpt_quarantine")


@pytest.mark.faultinject
def test_member_write_fault_leaves_save_retryable(tmp_path):
    """A fault at ``io.member_write`` (before any payload byte lands)
    must leave history untouched and the save cleanly retryable."""
    import jax.numpy as jnp
    from paddle_tpu.framework import faultinject
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.io import load_checkpoint, save_checkpoint
    d = _two_step_ckpt_dir(tmp_path)
    sc = Scope()
    with scope_guard(sc):
        sc.set_var("w_q", jnp.ones(4, jnp.float32) * 3)
        with faultinject.failpoints(["io.member_write:raise"]):
            with pytest.raises(OSError, match="member_write"):
                save_checkpoint(None, d, step=3)
        assert not os.path.exists(
            os.path.join(d, "step_3", "manifest.json"))
        with open(os.path.join(d, "latest")) as f:
            assert f.read().strip() == "step_2"
        save_checkpoint(None, d, step=3)    # plain retry, no cleanup
    s2 = Scope()
    with scope_guard(s2):
        assert load_checkpoint(None, d) == 3
        np.testing.assert_allclose(np.asarray(s2.find_var("w_q")),
                                   np.ones(4) * 3)


def test_checkpoint_commit_fsyncs_payload_and_directory(tmp_path,
                                                        monkeypatch):
    """The commit path fsyncs the shard file, the manifest, AND the
    directory entries — without all three, a power cut after the
    atomic rename can publish a valid-looking name over torn
    page-cache payloads (the exact hole ISSUE-17 closes)."""
    import jax.numpy as jnp
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.io import save_checkpoint
    real_fsync, fds = os.fsync, []
    monkeypatch.setattr(
        os, "fsync", lambda fd: (fds.append(fd), real_fsync(fd))[1])
    sc = Scope()
    with scope_guard(sc):
        sc.set_var("w_q", jnp.ones(4, jnp.float32))
        save_checkpoint(None, str(tmp_path), step=1)
    # shard npz + manifest + latest, each followed by its directory
    # entry: at least 3 file fsyncs and 3 directory fsyncs
    assert len(fds) >= 6
