"""Executor trainer-loop tests (train_from_dataset / prefetch)."""
import numpy as np


def test_train_from_dataset_runs_all_batches():
    """Executor.train_from_dataset: prefetch loop drives the jitted step
    over a Dataset (trainer_factory/device_worker equivalent)."""
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer

    class ListDataset(object):
        def __init__(self, batches):
            self._batches = batches

        def __iter__(self):
            return iter(self._batches)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], "float32")
        y = layers.fc(x, size=1)
        lbl = layers.data("y", [1], "float32")
        loss = layers.reduce_mean(layers.square_error_cost(y, lbl))
        optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    batches = [{"x": rng.rand(8, 4).astype(np.float32),
                "y": rng.rand(8, 1).astype(np.float32)} for _ in range(7)]
    steps, last = exe.train_from_dataset(main, ListDataset(batches),
                                         fetch_list=[loss])
    assert steps == 7
    assert np.isfinite(np.asarray(last[0])).all()
    # loss decreased over the pass
    l_again = exe.run(main, feed=batches[0], fetch_list=[loss])[0]
    assert np.isfinite(l_again).all()


def test_train_from_dataset_windowed_matches_per_step():
    """steps_per_dispatch=3: same dataset pass (windows + tail) produces
    the same final parameters as the per-step loop."""
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer
    from paddle_tpu.framework.scope import Scope, scope_guard

    def build():
        main, startup = pt.Program(), pt.Program()
        with pt.unique_name.guard(), pt.program_guard(main, startup):
            x = layers.data("x", [4], "float32")
            y = layers.fc(x, size=1, name="wfc")
            lbl = layers.data("y", [1], "float32")
            loss = layers.reduce_mean(layers.square_error_cost(y, lbl))
            optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(1)
    batches = [{"x": rng.rand(8, 4).astype(np.float32),
                "y": rng.rand(8, 1).astype(np.float32)} for _ in range(7)]

    results = []
    for w in (1, 3):
        main, startup, loss = build()
        sc = Scope()
        with scope_guard(sc):
            exe = pt.Executor()
            exe.run(startup)
            steps, last = exe.train_from_dataset(
                main, batches, fetch_list=[loss], steps_per_dispatch=w)
            assert steps == 7
            results.append({n: np.asarray(v) for n, v in sc.items()
                            if v is not None and
                            np.asarray(v).dtype.kind == "f"})
    for n, ref in results[0].items():
        np.testing.assert_allclose(results[1][n], ref, rtol=1e-6,
                                   atol=1e-6, err_msg=n)


def test_train_from_dataset_windowed_handles_ragged_batches():
    """A ragged batch (remainder / bucketed length) inside a window must
    degrade to per-step execution, not crash the epoch."""
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer
    from paddle_tpu.framework.scope import Scope, scope_guard

    main, startup = pt.Program(), pt.Program()
    with pt.unique_name.guard(), pt.program_guard(main, startup):
        x = layers.data("x", [4], "float32")
        y = layers.fc(x, size=1)
        lbl = layers.data("y", [1], "float32")
        loss = layers.reduce_mean(layers.square_error_cost(y, lbl))
        optimizer.SGD(0.1).minimize(loss)

    rng = np.random.RandomState(2)

    def mk(n):
        return {"x": rng.rand(n, 4).astype(np.float32),
                "y": rng.rand(n, 1).astype(np.float32)}

    batches = [mk(8), mk(8), mk(4), mk(8), mk(8)]   # ragged mid-window
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        steps, last = exe.train_from_dataset(
            main, batches, fetch_list=[loss], steps_per_dispatch=3)
    assert steps == 5
    assert np.isfinite(np.asarray(last[0])).all()


def test_prefetch_iterator_propagates_errors():
    from paddle_tpu.trainer_factory import PrefetchIterator

    def gen():
        yield 1
        raise RuntimeError("boom")

    it = PrefetchIterator(gen())
    assert next(it) == 1
    import pytest
    with pytest.raises(RuntimeError):
        for _ in it:
            pass


def test_wrong_rank_feed_named_error():
    """A wrong-rank feed must fail at the feed boundary with the var's
    name, not as a jax shape error deep inside the trace."""
    import pytest
    import paddle_tpu as pt
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("rank_x", [4], dtype="float32")
        y = layers.scale(x, scale=2.0)
    exe = pt.Executor()
    exe.run(startup)
    with pytest.raises(ValueError, match="rank_x.*rank"):
        exe.run(main, feed={"rank_x": np.ones(4, np.float32)},  # rank 1
                fetch_list=[y])                                 # wants 2


def test_infer_from_dataset_rejects_training_program():
    """infer_from_dataset must refuse a program with parameter-update ops
    (reference executor.py:1061 disables gradient push; ours validates) —
    and accept the for_test clone of the same model."""
    import pytest
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer

    class ListDataset(object):
        def __init__(self, batches):
            self._batches = batches

        def __iter__(self):
            return iter(self._batches)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], "float32")
        y = layers.fc(x, size=1)
        lbl = layers.data("y", [1], "float32")
        loss = layers.reduce_mean(layers.square_error_cost(y, lbl))
        test_prog = main.clone(for_test=True)
        optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    batches = [{"x": rng.rand(8, 4).astype(np.float32),
                "y": rng.rand(8, 1).astype(np.float32)} for _ in range(3)]
    with pytest.raises(ValueError, match="parameter-update ops"):
        exe.infer_from_dataset(main, ListDataset(batches),
                               fetch_list=[loss])
    steps, last = exe.infer_from_dataset(test_prog, ListDataset(batches),
                                         fetch_list=[loss])
    assert steps == 3
    assert np.isfinite(np.asarray(last[0])).all()


def test_train_from_dataset_windows_pipeline_program():
    """steps_per_dispatch on a fleet pipeline program routes through
    Executor._run_pipeline_steps (one fused scan per window) and matches
    the per-step loop exactly."""
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer
    from paddle_tpu.distributed import fleet, init_mesh, DistributedStrategy
    from paddle_tpu.distributed.pipeline_program import pp_stage_guard
    from paddle_tpu.framework.scope import Scope, scope_guard

    class ListDataset(object):
        def __init__(self, batches):
            self._batches = batches

        def __iter__(self):
            return iter(self._batches)

    n_stage, dm, batch, W = 2, 8, 8, 4
    rng = np.random.RandomState(3)
    batches = [{"pp_x": rng.randn(batch, dm).astype(np.float32),
                "pp_y": rng.randn(batch, dm).astype(np.float32)}
               for _ in range(W)]

    def build():
        init_mesh({"dp": 2, "pp": n_stage})
        strategy = DistributedStrategy()
        strategy.mesh_axes = {"dp": 2, "pp": n_stage}
        strategy.pipeline = True
        strategy.pp_schedule = "1f1b"
        strategy.pp_num_micro = 2
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("pp_x", [batch, dm], "float32",
                            append_batch_size=False)
            h = x
            for s in range(n_stage):
                with pp_stage_guard(s):
                    h = layers.fc(h, size=dm, act="tanh")
            y = layers.data("pp_y", [batch, dm], "float32",
                            append_batch_size=False)
            loss = layers.reduce_mean(layers.square(h - y))
            fleet.distributed_optimizer(optimizer.SGD(0.1),
                                        strategy).minimize(loss)
        return main, startup, loss

    def run(steps_per_dispatch):
        main, startup, loss = build()
        with scope_guard(Scope()):
            exe = pt.Executor()
            exe.run(startup)
            steps, last = exe.train_from_dataset(
                main, ListDataset(batches), fetch_list=[loss],
                steps_per_dispatch=steps_per_dispatch)
        return steps, float(np.asarray(last[0]).reshape(-1)[-1])

    s1, l1 = run(1)
    s2, l2 = run(2)
    assert s1 == s2 == W
    np.testing.assert_allclose(l1, l2, rtol=1e-5, atol=1e-6)
