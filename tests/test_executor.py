"""Executor trainer-loop tests (train_from_dataset / prefetch)."""
import numpy as np


def test_train_from_dataset_runs_all_batches():
    """Executor.train_from_dataset: prefetch loop drives the jitted step
    over a Dataset (trainer_factory/device_worker equivalent)."""
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer

    class ListDataset(object):
        def __init__(self, batches):
            self._batches = batches

        def __iter__(self):
            return iter(self._batches)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], "float32")
        y = layers.fc(x, size=1)
        lbl = layers.data("y", [1], "float32")
        loss = layers.reduce_mean(layers.square_error_cost(y, lbl))
        optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    batches = [{"x": rng.rand(8, 4).astype(np.float32),
                "y": rng.rand(8, 1).astype(np.float32)} for _ in range(7)]
    steps, last = exe.train_from_dataset(main, ListDataset(batches),
                                         fetch_list=[loss])
    assert steps == 7
    assert np.isfinite(np.asarray(last[0])).all()
    # loss decreased over the pass
    l_again = exe.run(main, feed=batches[0], fetch_list=[loss])[0]
    assert np.isfinite(l_again).all()


def test_prefetch_iterator_propagates_errors():
    from paddle_tpu.trainer_factory import PrefetchIterator

    def gen():
        yield 1
        raise RuntimeError("boom")

    it = PrefetchIterator(gen())
    assert next(it) == 1
    import pytest
    with pytest.raises(RuntimeError):
        for _ in it:
            pass


def test_wrong_rank_feed_named_error():
    """A wrong-rank feed must fail at the feed boundary with the var's
    name, not as a jax shape error deep inside the trace."""
    import pytest
    import paddle_tpu as pt
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("rank_x", [4], dtype="float32")
        y = layers.scale(x, scale=2.0)
    exe = pt.Executor()
    exe.run(startup)
    with pytest.raises(ValueError, match="rank_x.*rank"):
        exe.run(main, feed={"rank_x": np.ones(4, np.float32)},  # rank 1
                fetch_list=[y])                                 # wants 2
